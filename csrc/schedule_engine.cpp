// adapcc-tpu native schedule engine.
//
// The reference keeps its graph machinery native: tinyxml2 parses strategy
// trees, treeDFS builds per-rank role tables, and control.cu computes relay
// roles (reference csrc/allreduce.cu:52-104, csrc/control.cu:27-101).  On
// TPU the data plane is XLA's, but the host-side schedule work — parsing,
// role tables, round lowering, relay pruning — still runs per
// reconstruction and scales with world size, so it lives here as C++ with a
// plain C ABI consumed via ctypes (no pybind11 in the image).
//
// Semantics are kept in lockstep with the Python implementation
// (adapcc_tpu/strategy/ir.py, adapcc_tpu/comm/relay.py); the pytest suite
// asserts parity on every fixture.

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <memory>
#include <set>
#include <string>
#include <vector>

namespace {

// --------------------------------------------------------------------------
// minimal lenient XML reader for the strategy schema:
//   <trees><root id='..' ip='..'><gpu id='..' ip='..'>...</gpu></root></trees>
// Handles the reference fixtures' missing space between attributes
// (strategy/4.xml: id='1'ip='...').
// --------------------------------------------------------------------------

struct XmlNode {
  std::string tag;
  std::map<std::string, std::string> attrs;
  std::vector<XmlNode> children;
};

struct Parser {
  const char* p;
  const char* end;
  bool ok = true;

  explicit Parser(const std::string& s) : p(s.data()), end(s.data() + s.size()) {}

  void skip_ws() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool parse_node(XmlNode* out) {
    skip_ws();
    if (p >= end || *p != '<') return fail();
    ++p;
    if (p < end && (*p == '?' || *p == '!')) {  // prolog/comment: skip to '>'
      while (p < end && *p != '>') ++p;
      if (p < end) ++p;
      return parse_node(out);
    }
    out->tag.clear();
    while (p < end && !strchr(" \t\r\n/>", *p)) out->tag.push_back(*p++);
    // attributes
    for (;;) {
      skip_ws();
      if (p >= end) return fail();
      if (*p == '/') {  // self-closing
        ++p;
        skip_ws();
        if (p >= end || *p != '>') return fail();
        ++p;
        return true;
      }
      if (*p == '>') {
        ++p;
        break;
      }
      std::string name;
      while (p < end && *p != '=' && !strchr(" \t\r\n", *p)) name.push_back(*p++);
      skip_ws();
      if (p >= end || *p != '=') return fail();
      ++p;
      skip_ws();
      if (p >= end || (*p != '\'' && *p != '"')) return fail();
      char q = *p++;
      std::string val;
      while (p < end && *p != q) val.push_back(*p++);
      if (p >= end) return fail();
      ++p;  // closing quote; a following attribute may start immediately
      out->attrs[name] = val;
    }
    // children until matching close tag
    for (;;) {
      skip_ws();
      if (p >= end) return fail();
      if (*p == '<' && p + 1 < end && p[1] == '/') {
        p += 2;
        std::string close;
        while (p < end && *p != '>') close.push_back(*p++);
        if (p < end) ++p;
        // trim
        while (!close.empty() && strchr(" \t\r\n", close.back())) close.pop_back();
        return close == out->tag ? true : fail();
      }
      if (*p == '<') {
        out->children.emplace_back();
        if (!parse_node(&out->children.back())) return false;
      } else {
        ++p;  // text content: ignored by the schema
      }
    }
  }

  bool fail() {
    ok = false;
    return false;
  }
};

// --------------------------------------------------------------------------
// strategy model + round lowering (parity with strategy/ir.py)
// --------------------------------------------------------------------------

struct Tree {
  int root = -1;
  std::map<int, std::vector<int>> children;
  std::map<int, int> parent;
  std::map<int, std::string> ips;

  std::vector<int> postorder(int start) const {
    std::vector<int> order;
    std::vector<std::pair<int, bool>> stack{{start, false}};
    while (!stack.empty()) {
      auto [r, done] = stack.back();
      stack.pop_back();
      if (done) {
        order.push_back(r);
        continue;
      }
      stack.push_back({r, true});
      auto it = children.find(r);
      if (it != children.end())
        for (auto c = it->second.rbegin(); c != it->second.rend(); ++c)
          stack.push_back({*c, false});
    }
    return order;
  }

  // edges in dependency order; packed into partial-permutation rounds
  // (same greedy rule as ir.py::_pack_rounds)
  std::vector<std::vector<std::pair<int, int>>> pack(
      const std::vector<std::pair<int, int>>& edges) const {
    std::vector<std::vector<std::pair<int, int>>> rounds;
    std::vector<std::set<int>> srcs, dsts;
    std::map<int, int> landed;
    for (auto [s, d] : edges) {
      size_t r = landed.count(s) ? landed[s] + 1 : 0;
      while (r < rounds.size() && (srcs[r].count(s) || dsts[r].count(d))) ++r;
      while (r >= rounds.size()) {
        rounds.emplace_back();
        srcs.emplace_back();
        dsts.emplace_back();
      }
      rounds[r].push_back({s, d});
      srcs[r].insert(s);
      dsts[r].insert(d);
      auto it = landed.find(d);
      landed[d] = it == landed.end() ? (int)r : std::max(it->second, (int)r);
    }
    return rounds;
  }

  std::vector<std::vector<std::pair<int, int>>> reduce_rounds() const {
    std::vector<std::pair<int, int>> edges;
    for (int r : postorder(root))
      if (r != root) edges.push_back({r, parent.at(r)});
    return pack(edges);
  }

  std::vector<std::vector<std::pair<int, int>>> broadcast_rounds() const {
    std::vector<std::pair<int, int>> edges;
    std::vector<int> queue{root};
    for (size_t i = 0; i < queue.size(); ++i) {
      int r = queue[i];
      if (r != root) edges.push_back({parent.at(r), r});
      auto it = children.find(r);
      if (it != children.end())
        for (int c : it->second) queue.push_back(c);
    }
    return pack(edges);
  }

  // ranks whose subtree holds an active rank (relay.py::live_ranks)
  std::set<int> live_ranks(const uint8_t* active) const {
    std::set<int> live;
    for (int r : postorder(root)) {
      bool l = active[r] != 0;
      auto it = children.find(r);
      if (!l && it != children.end())
        for (int c : it->second)
          if (live.count(c)) {
            l = true;
            break;
          }
      if (l) live.insert(r);
    }
    return live;
  }
};

struct Strategy {
  std::vector<Tree> trees;
  int world_size = 0;
  std::string error;
};

void walk_gpu(const XmlNode& node, int parent_rank, Tree* tree, Strategy* s,
              std::set<int>* seen) {
  auto it = node.attrs.find("id");
  if (it == node.attrs.end()) {
    s->error = "element missing id attribute";
    return;
  }
  char* parse_end = nullptr;
  long rank_l = strtol(it->second.c_str(), &parse_end, 10);
  // negative or junk ids would index the active mask out of bounds later
  if (parse_end == it->second.c_str() || *parse_end != '\0' || rank_l < 0 ||
      rank_l > 1 << 24) {
    s->error = "invalid rank id: " + it->second;
    return;
  }
  int rank = (int)rank_l;
  // a rank may appear once per tree; a repeat is a second parent or a cycle
  // (the self-rooted case slips past the parent check, then an unchecked
  // lowering would walk the loop forever)
  if (!seen->insert(rank).second) {
    s->error = "rank appears twice in one tree (cycle or duplicate parent)";
    return;
  }
  auto ip = node.attrs.find("ip");
  tree->ips[rank] = ip == node.attrs.end() ? "" : ip->second;
  if (parent_rank >= 0) {
    tree->children[parent_rank].push_back(rank);
    tree->parent[rank] = parent_rank;
  } else {
    tree->root = rank;
  }
  if (rank + 1 > s->world_size) s->world_size = rank + 1;
  for (const auto& c : node.children) {
    if (c.tag == "gpu") walk_gpu(c, rank, tree, s, seen);
    if (!s->error.empty()) return;
  }
}

// --------------------------------------------------------------------------
// ParTrees synthesis (parity with strategy/partrees.py)
// --------------------------------------------------------------------------

struct MasterInfo {
  int rank;
  std::vector<int> group;  // all ranks on this host, master first
  double bdp;              // bandwidth-delay product of the outbound link
};

// '\n'-joined list → entries.  N entries arrive with N−1 separators, so the
// final (possibly empty) entry is always emitted — dropping it would reject
// legal empty-string ips with a wrong "size mismatch" diagnosis.
std::vector<std::string> split_lines(const char* joined) {
  std::vector<std::string> out;
  if (!joined) return out;
  std::string cur;
  for (const char* p = joined; *p; ++p) {
    if (*p == '\n') {
      out.push_back(cur);
      cur.clear();
    } else {
      cur.push_back(*p);
    }
  }
  out.push_back(cur);
  return out;
}

// Consecutive ranks sharing the master's ip form its host group; a group
// also ends at the next master (partrees.py::_host_groups).
std::map<int, std::vector<int>> host_groups(const std::vector<std::string>& ips,
                                            const std::vector<int>& masters) {
  std::set<int> master_set(masters.begin(), masters.end());
  std::map<int, std::vector<int>> groups;
  for (int m : masters) {
    std::vector<int> group{m};
    for (int r = m + 1; r < (int)ips.size() && ips[r] == ips[m] && !master_set.count(r); ++r)
      group.push_back(r);
    groups[m] = std::move(group);
  }
  return groups;
}

Tree build_partree(const std::vector<MasterInfo>& order,
                   const std::map<int, std::vector<int>>& groups,
                   const std::vector<std::string>& ips) {
  Tree t;
  t.root = order[0].rank;
  // array-heap binary tree over the masters (partrees.py::_heap_tree_edges)
  for (size_t i = 0; i < order.size(); ++i) {
    for (size_t j : {2 * i + 1, 2 * i + 2}) {
      if (j < order.size()) {
        t.children[order[i].rank].push_back(order[j].rank);
        t.parent[order[j].rank] = order[i].rank;
      }
    }
  }
  // chain policy: intra-host ranks beneath their master, chain head FIRST so
  // the sibling index favors the fast local edge (partrees.py::_attach_chains)
  for (const auto& m : order) {
    const auto& group = groups.at(m.rank);
    if (group.size() < 2) continue;
    auto& kids = t.children[m.rank];
    kids.insert(kids.begin(), group[1]);
    t.parent[group[1]] = m.rank;
    for (size_t i = 1; i + 1 < group.size(); ++i) {
      t.children[group[i]].push_back(group[i + 1]);
      t.parent[group[i + 1]] = group[i];
    }
  }
  for (size_t r = 0; r < ips.size(); ++r) t.ips[(int)r] = ips[r];
  return t;
}

}  // namespace

// --------------------------------------------------------------------------
// C ABI
// --------------------------------------------------------------------------

extern "C" {

void* adapcc_parse_strategy(const char* xml_text) {
  auto s = std::make_unique<Strategy>();
  std::string text(xml_text ? xml_text : "");
  Parser parser(text);
  XmlNode doc;
  if (!parser.parse_node(&doc) || doc.tag != "trees") {
    s->error = "malformed strategy xml (expected <trees>)";
    return s.release();
  }
  for (const auto& root_el : doc.children) {
    if (root_el.tag != "root") continue;
    Tree t;
    std::set<int> seen;
    walk_gpu(root_el, -1, &t, s.get(), &seen);
    if (!s->error.empty()) return s.release();
    s->trees.push_back(std::move(t));
  }
  if (s->trees.empty() && s->error.empty()) s->error = "no <root> trees";
  return s.release();
}

// ParTrees synthesis: ip_table is '\n'-joined (world entries); bw/lat are
// world×world row-major.  Returns a Strategy handle compatible with every
// query/lowering entry point below; check adapcc_error before use.
void* adapcc_synthesize_partrees(const char* ip_table_joined, const int32_t* masters,
                                 int n_masters, int parallel_degree, const double* bw,
                                 const double* lat, int world) {
  auto s = std::make_unique<Strategy>();
  auto ips = split_lines(ip_table_joined);
  if ((int)ips.size() != world || world <= 0) {
    s->error = "ip table size does not match world";
    return s.release();
  }
  if (n_masters <= 0) {
    s->error = "need at least one master";
    return s.release();
  }
  std::vector<int> master_ranks;
  std::set<int> seen_masters;
  for (int i = 0; i < n_masters; ++i) {
    int m = masters[i];
    if (m < 0 || m >= world) {
      s->error = "master rank out of range";
      return s.release();
    }
    // a duplicate would build a self-parenting tree and hang any lowering
    if (!seen_masters.insert(m).second) {
      s->error = "duplicate master rank";
      return s.release();
    }
    master_ranks.push_back(m);
  }
  auto groups = host_groups(ips, master_ranks);

  std::vector<MasterInfo> infos;
  for (int m : master_ranks) {
    // probe target: first rank of the "next" host around the ring —
    // this master's representative outbound inter-host link
    int peer = (m + (int)groups[m].size()) % world;
    MasterInfo mi;
    mi.rank = m;
    mi.group = groups[m];
    mi.bdp = bw[m * world + peer] * lat[m * world + peer];
    infos.push_back(std::move(mi));
  }
  // best-provisioned first; stable to match Python's tie behavior
  std::stable_sort(infos.begin(), infos.end(),
                   [](const MasterInfo& a, const MasterInfo& b) { return a.bdp > b.bdp; });

  int degree = std::min((int)infos.size(), std::max(1, parallel_degree));
  std::vector<MasterInfo> rotation = infos;
  for (int t = 0; t < degree; ++t) {
    if (t > 0) std::rotate(rotation.begin(), rotation.begin() + 1, rotation.end());
    s->trees.push_back(build_partree(rotation, groups, ips));
  }
  s->world_size = world;
  return s.release();
}

void adapcc_free_strategy(void* h) { delete static_cast<Strategy*>(h); }

const char* adapcc_error(void* h) {
  auto* s = static_cast<Strategy*>(h);
  return s->error.empty() ? nullptr : s->error.c_str();
}

int adapcc_world_size(void* h) { return static_cast<Strategy*>(h)->world_size; }
int adapcc_num_trees(void* h) { return (int)static_cast<Strategy*>(h)->trees.size(); }

int adapcc_tree_root(void* h, int t) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  return s->trees[t].root;
}

// rank→ip for tree t; NULL for unknown tree/rank.  The pointer stays valid
// until adapcc_free_strategy.
const char* adapcc_tree_ip(void* h, int t, int rank) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return nullptr;
  auto& ips = s->trees[t].ips;
  auto it = ips.find(rank);
  return it == ips.end() ? nullptr : it->second.c_str();
}

// Lower rounds into caller buffers.  edges_out receives (src, dst) pairs
// flattened; offsets_out[i] = first edge index of round i, plus a final
// sentinel = total edges.  Returns the number of rounds, or -1 if the
// buffers are too small / tree index invalid.
static int emit_rounds(const std::vector<std::vector<std::pair<int, int>>>& rounds,
                       int32_t* edges_out, int32_t* offsets_out, int max_edges,
                       int max_rounds) {
  int n_edges = 0;
  for (const auto& r : rounds) n_edges += (int)r.size();
  if ((int)rounds.size() > max_rounds || n_edges > max_edges) return -1;
  int e = 0;
  for (size_t i = 0; i < rounds.size(); ++i) {
    offsets_out[i] = e;
    for (auto [s, d] : rounds[i]) {
      edges_out[2 * e] = s;
      edges_out[2 * e + 1] = d;
      ++e;
    }
  }
  offsets_out[rounds.size()] = e;
  return (int)rounds.size();
}

int adapcc_reduce_rounds(void* h, int t, int32_t* edges_out, int32_t* offsets_out,
                         int max_edges, int max_rounds) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  return emit_rounds(s->trees[t].reduce_rounds(), edges_out, offsets_out, max_edges,
                     max_rounds);
}

int adapcc_broadcast_rounds(void* h, int t, int32_t* edges_out, int32_t* offsets_out,
                            int max_edges, int max_rounds) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  return emit_rounds(s->trees[t].broadcast_rounds(), edges_out, offsets_out, max_edges,
                     max_rounds);
}

// Relay-pruned variants: edges whose liveness test fails are dropped and
// empty rounds elided (parity with relay.py::prune_*_rounds).
int adapcc_prune_reduce_rounds(void* h, int t, const uint8_t* active,
                               int32_t* edges_out, int32_t* offsets_out,
                               int max_edges, int max_rounds) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  const Tree& tree = s->trees[t];
  auto live = tree.live_ranks(active);
  std::vector<std::vector<std::pair<int, int>>> kept;
  for (const auto& rnd : tree.reduce_rounds()) {
    std::vector<std::pair<int, int>> k;
    for (auto [src, dst] : rnd)
      if (live.count(src)) k.push_back({src, dst});
    if (!k.empty()) kept.push_back(std::move(k));
  }
  return emit_rounds(kept, edges_out, offsets_out, max_edges, max_rounds);
}

int adapcc_prune_broadcast_rounds(void* h, int t, const uint8_t* active,
                                  int32_t* edges_out, int32_t* offsets_out,
                                  int max_edges, int max_rounds) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  const Tree& tree = s->trees[t];
  auto live = tree.live_ranks(active);
  std::vector<std::vector<std::pair<int, int>>> kept;
  for (const auto& rnd : tree.broadcast_rounds()) {
    std::vector<std::pair<int, int>> k;
    for (auto [src, dst] : rnd)
      if (live.count(dst)) k.push_back({src, dst});
    if (!k.empty()) kept.push_back(std::move(k));
  }
  return emit_rounds(kept, edges_out, offsets_out, max_edges, max_rounds);
}

// Relay role bits for one rank (control.cu parity):
// bit0 hasRecv, bit1 hasLocal, bit2 hasKernel, bit3 hasSend.
int adapcc_relay_role(void* h, int t, int rank, const uint8_t* active) {
  auto* s = static_cast<Strategy*>(h);
  if (t < 0 || t >= (int)s->trees.size()) return -1;
  const Tree& tree = s->trees[t];
  auto live = tree.live_ranks(active);
  int n_live_recv = 0;
  auto it = tree.children.find(rank);
  if (it != tree.children.end())
    for (int c : it->second)
      if (live.count(c)) ++n_live_recv;
  bool has_local = active[rank] != 0;
  bool has_recv = n_live_recv > 0;
  bool has_kernel = has_recv && (n_live_recv + (has_local ? 1 : 0)) >= 2;
  bool has_send = rank != tree.root && live.count(rank);
  return (has_recv ? 1 : 0) | (has_local ? 2 : 0) | (has_kernel ? 4 : 0) |
         (has_send ? 8 : 0);
}

}  // extern "C"
